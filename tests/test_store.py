"""Watchable store + StoreAdapter tests (apiserver/informer-wiring analog;
reference: controller-runtime informer plumbing + envtest-style integration
suites in test/integration/controller/core/)."""

import dataclasses

import pytest

from kueue_tpu import webhooks
from kueue_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    Workload,
    WorkloadPriorityClass,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.controllers.store import (
    ADDED,
    DELETED,
    KIND_CLUSTER_QUEUE,
    KIND_LOCAL_QUEUE,
    KIND_RESOURCE_FLAVOR,
    KIND_WORKLOAD,
    MODIFIED,
    Store,
    StoreAdapter,
)


def cq_obj(name="cq", cpu=10):
    return ClusterQueue(
        name=name,
        resource_groups=(ResourceGroup(
            covered_resources=("cpu",),
            flavors=(FlavorQuotas.make("default", cpu=cpu),)),))


class TestStore:
    def test_crud_and_versions(self):
        s = Store()
        rf = ResourceFlavor.make("default")
        s.create(KIND_RESOURCE_FLAVOR, rf)
        assert s.get(KIND_RESOURCE_FLAVOR, "default") is rf
        v1 = s.resource_version(KIND_RESOURCE_FLAVOR, "default")
        s.update(KIND_RESOURCE_FLAVOR, rf)
        assert s.resource_version(KIND_RESOURCE_FLAVOR, "default") > v1
        assert s.delete(KIND_RESOURCE_FLAVOR, "default") is rf
        assert s.get(KIND_RESOURCE_FLAVOR, "default") is None

    def test_create_duplicate_rejected(self):
        s = Store()
        s.create(KIND_CLUSTER_QUEUE, cq_obj())
        with pytest.raises(ValueError):
            s.create(KIND_CLUSTER_QUEUE, cq_obj())

    def test_webhook_validation_at_boundary(self):
        s = Store()
        bad = ClusterQueue(
            name="cq",
            resource_groups=(ResourceGroup(
                covered_resources=("cpu",),
                flavors=(FlavorQuotas.make("f", cpu=(10, 5)),)),))
        with pytest.raises(webhooks.ValidationError):
            s.create(KIND_CLUSTER_QUEUE, bad)

    def test_webhook_defaulting_at_boundary(self):
        s = Store()
        wl = Workload(name="w", pod_sets=[PodSet.make("", 1, cpu=1)])
        s.create(KIND_WORKLOAD, wl)
        assert wl.pod_sets[0].name == "main"

    def test_update_immutability(self):
        s = Store()
        s.create(KIND_CLUSTER_QUEUE, cq_obj())
        changed = dataclasses.replace(cq_obj(), queueing_strategy="StrictFIFO")
        with pytest.raises(webhooks.ValidationError):
            s.update(KIND_CLUSTER_QUEUE, changed)

    def test_watch_replay_and_events(self):
        s = Store()
        s.create(KIND_RESOURCE_FLAVOR, ResourceFlavor.make("default"))
        events = []
        s.watch(KIND_RESOURCE_FLAVOR, events.append)
        assert [e.type for e in events] == [ADDED]  # initial replay
        s.create(KIND_RESOURCE_FLAVOR, ResourceFlavor.make("spot"))
        s.delete(KIND_RESOURCE_FLAVOR, "spot")
        assert [e.type for e in events] == [ADDED, ADDED, DELETED]

    def test_namespaced_list(self):
        s = Store()
        s.create(KIND_LOCAL_QUEUE,
                 LocalQueue(name="a", namespace="ns1", cluster_queue="cq"))
        s.create(KIND_LOCAL_QUEUE,
                 LocalQueue(name="b", namespace="ns2", cluster_queue="cq"))
        assert [lq.name for lq in s.list(KIND_LOCAL_QUEUE, "ns1")] == ["a"]


class TestStoreAdapter:
    def test_end_to_end_admission_via_store(self):
        s = Store()
        fw = Framework()
        adapter = StoreAdapter(s, fw)
        s.create(KIND_RESOURCE_FLAVOR, ResourceFlavor.make("default"))
        s.create(KIND_CLUSTER_QUEUE, cq_obj())
        s.create(KIND_LOCAL_QUEUE,
                 LocalQueue(name="lq", namespace="default",
                            cluster_queue="cq"))
        wl = Workload(name="w", queue_name="lq",
                      pod_sets=[PodSet.make("main", 2, cpu=1)])
        s.create(KIND_WORKLOAD, wl)
        adapter.tick()
        # Status flowed back into the store view.
        stored = s.get(KIND_WORKLOAD, "default/w")
        assert stored.is_admitted
        assert stored.admission.cluster_queue == "cq"

    def test_objects_created_before_adapter_replay(self):
        # List-then-watch: the adapter picks up pre-existing objects.
        s = Store()
        s.create(KIND_RESOURCE_FLAVOR, ResourceFlavor.make("default"))
        s.create(KIND_CLUSTER_QUEUE, cq_obj())
        s.create(KIND_LOCAL_QUEUE,
                 LocalQueue(name="lq", namespace="default",
                            cluster_queue="cq"))
        s.create(KIND_WORKLOAD,
                 Workload(name="w", queue_name="lq",
                          pod_sets=[PodSet.make("main", 1, cpu=1)]))
        fw = Framework()
        adapter = StoreAdapter(s, fw)
        adapter.tick()
        assert s.get(KIND_WORKLOAD, "default/w").is_admitted

    def test_delete_workload_releases_quota(self):
        s = Store()
        fw = Framework()
        adapter = StoreAdapter(s, fw)
        s.create(KIND_RESOURCE_FLAVOR, ResourceFlavor.make("default"))
        s.create(KIND_CLUSTER_QUEUE, cq_obj(cpu=2))
        s.create(KIND_LOCAL_QUEUE,
                 LocalQueue(name="lq", namespace="default",
                            cluster_queue="cq"))
        w1 = Workload(name="w1", queue_name="lq",
                      pod_sets=[PodSet.make("main", 1, cpu=2)])
        s.create(KIND_WORKLOAD, w1)
        adapter.tick()
        assert s.get(KIND_WORKLOAD, "default/w1").is_admitted
        w2 = Workload(name="w2", queue_name="lq",
                      pod_sets=[PodSet.make("main", 1, cpu=2)])
        s.create(KIND_WORKLOAD, w2)
        adapter.tick()
        assert not w2.is_admitted
        s.delete(KIND_WORKLOAD, "default/w1")
        adapter.tick()
        assert w2.is_admitted

    def test_priority_class_resolution_via_store(self):
        s = Store()
        fw = Framework()
        StoreAdapter(s, fw)
        s.create(KIND_RESOURCE_FLAVOR, ResourceFlavor.make("default"))
        s.create(KIND_CLUSTER_QUEUE, cq_obj())
        s.create(KIND_LOCAL_QUEUE,
                 LocalQueue(name="lq", namespace="default",
                            cluster_queue="cq"))
        from kueue_tpu.controllers.store import KIND_WORKLOAD_PRIORITY_CLASS
        s.create(KIND_WORKLOAD_PRIORITY_CLASS,
                 WorkloadPriorityClass(name="vip", value=50))
        wl = Workload(name="w", queue_name="lq", priority_class="vip",
                      pod_sets=[PodSet.make("main", 1, cpu=1)])
        s.create(KIND_WORKLOAD, wl)
        assert fw.workloads["default/w"].priority == 50
