"""Topology-aware scheduling goldens (kueue_tpu/topology).

Acceptance scenarios from the subsystem's contract, each run under BOTH
the sequential referee and the batched device solver with identical
results: required lowest-level packing, preferred fallback across levels,
NO_FIT when no single domain can ever fit, same-tick cycle charging, the
ledger release on finish, and the fragmentation-reducing victim
preference under preemption. Plus device/host fit-kernel equivalence on
randomized instances, serialization roundtrips, and the no-op guarantee
for topology-free clusters.
"""

import numpy as np
import pytest

from kueue_tpu.api import serialization
from kueue_tpu.api.types import (
    Admission,
    ClusterQueuePreemption,
    PodSet,
    PodSetAssignment,
    ResourceFlavor,
    TopologyAssignment,
    TopologySpec,
    Workload,
)
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.models.flavor_fit import BatchSolver

from tests.util import fq, make_cq, make_flavor, make_lq, rg


@pytest.fixture(params=[False, True], ids=["referee", "batch"])
def batch(request):
    return request.param


def topo_flavor(name="tpu", counts=(1, 2, 2), leaf_capacity=2):
    return ResourceFlavor.make(
        name,
        topology=TopologySpec.uniform(("block", "rack", "host"),
                                      counts, leaf_capacity))


def build_fw(batch, cpu=100, counts=(1, 2, 2), leaf_capacity=2,
             preemption=None):
    fw = Framework(batch_solver=BatchSolver() if batch else None)
    fw.create_resource_flavor(topo_flavor(counts=counts,
                                          leaf_capacity=leaf_capacity))
    fw.create_cluster_queue(
        make_cq("cq", rg("cpu", fq("tpu", cpu=cpu)), preemption=preemption))
    fw.create_local_queue(make_lq("main", cq="cq"))
    return fw


def wl(name, count, required=None, preferred=None, priority=0,
       creation=100.0, cpu=1):
    return Workload(
        name=name, queue_name="main", priority=priority,
        creation_time=creation,
        pod_sets=[PodSet.make("main", count, topology_required=required,
                              topology_preferred=preferred, cpu=cpu)])


def ta_of(fw, name):
    w = fw.workloads[f"default/{name}"]
    assert w.admission is not None, f"{name} not admitted"
    return w.admission.pod_set_assignments[0].topology_assignment


# ---------------------------------------------------------------------------
# required: lowest-level (deepest) packing
# ---------------------------------------------------------------------------


def test_required_packs_lowest_fitting_level(batch):
    # host capacity 4: a 3-pod rack-required podset packs a single HOST
    # (the lowest domain that fits), not just any rack.
    fw = build_fw(batch, counts=(1, 2, 2), leaf_capacity=4)
    fw.submit(wl("a", 3, required="rack"))
    assert fw.run_until_settled() == 1
    ta = ta_of(fw, "a")
    assert ta.flavor == "tpu"
    assert ta.levels == ("block", "rack", "host")
    assert len(ta.domain) == 3
    assert sum(n for _, n in ta.counts) == 3
    assert len(ta.counts) == 1  # one host holds all three pods


def test_required_spreads_within_one_domain_when_no_leaf_fits(batch):
    # 3 pods, host capacity 2: no single host fits, but rack0 (4 slots)
    # does — pods pack hosts of ONE rack.
    fw = build_fw(batch, counts=(1, 2, 2), leaf_capacity=2)
    fw.submit(wl("a", 3, required="rack"))
    assert fw.run_until_settled() == 1
    ta = ta_of(fw, "a")
    assert ta.levels == ("block", "rack")
    assert sum(n for _, n in ta.counts) == 3
    leaves = [i for i, _ in ta.counts]
    assert leaves == sorted(leaves) and max(leaves) <= 1  # rack0 = leaves 0,1


# ---------------------------------------------------------------------------
# preferred: fallback across levels, then unconstrained
# ---------------------------------------------------------------------------


def test_preferred_falls_back_up_the_hierarchy(batch):
    # 6 pods preferred rack: racks hold 4, the block holds 8 — falls back
    # to the block domain instead of failing.
    fw = build_fw(batch, counts=(1, 2, 2), leaf_capacity=2)
    fw.submit(wl("a", 6, preferred="rack"))
    assert fw.run_until_settled() == 1
    ta = ta_of(fw, "a")
    assert ta.levels == ("block",)
    assert ta.domain == ("block0",)
    assert sum(n for _, n in ta.counts) == 6


def test_preferred_places_unconstrained_when_nothing_fits(batch):
    # 9 pods > whole tree (8 slots): preferred degrades to unconstrained
    # placement (admitted, no topology assignment, no ledger charge).
    fw = build_fw(batch, counts=(1, 2, 2), leaf_capacity=2)
    fw.submit(wl("a", 9, preferred="rack"))
    assert fw.run_until_settled() == 1
    assert ta_of(fw, "a") is None
    assert not fw.cache.topology.flavors["tpu"].any()


# ---------------------------------------------------------------------------
# required: NO_FIT / requeue semantics
# ---------------------------------------------------------------------------


def test_required_no_fit_when_no_domain_can_ever_fit(batch):
    # 5 pods required rack, rack capacity 4: permanent NO_FIT.
    fw = build_fw(batch, counts=(1, 2, 2), leaf_capacity=2)
    fw.submit(wl("a", 5, required="rack"))
    assert fw.run_until_settled() == 0
    w = fw.workloads["default/a"]
    assert not w.has_quota_reservation
    cond = w.find_condition("QuotaReserved")
    assert cond is not None and "can ever fit" in cond.message


def test_required_blocked_by_occupancy_admits_after_release(batch):
    fw = build_fw(batch, counts=(1, 2, 2), leaf_capacity=2)
    fw.submit(wl("a", 3, required="rack"))
    assert fw.run_until_settled() == 1
    # rack0 now has 1 free slot, rack1 has 4: a 2-pod required podset
    # best-fits rack1 (rack0 cannot hold it).
    fw.submit(wl("b", 2, required="rack"))
    assert fw.run_until_settled() == 1
    assert ta_of(fw, "b").domain[:2] == ("block0", "rack1")
    # A 4-pod required podset is blocked by occupancy (rack capacity 4
    # exists, so NOT a permanent NO_FIT) ...
    fw.submit(wl("c", 4, required="rack"))
    assert fw.run_until_settled() == 0
    w = fw.workloads["default/c"]
    assert not w.has_quota_reservation
    assert "insufficient free capacity" in w.find_condition(
        "QuotaReserved").message
    # ... until a release frees a contiguous rack.
    fw.finish(fw.workloads["default/a"])
    fw.finish(fw.workloads["default/b"])
    assert fw.run_until_settled() == 1
    assert ta_of(fw, "c") is not None


def test_same_tick_admissions_share_occupancy(batch):
    # Two 3-pod rack-required podsets in ONE tick: both solve against the
    # same empty snapshot, but the admission cycle's side-tracked charge
    # must route them to different racks.
    fw = build_fw(batch, counts=(1, 2, 2), leaf_capacity=2)
    fw.submit(wl("a", 3, required="rack", creation=1.0))
    fw.submit(wl("b", 3, required="rack", creation=2.0))
    assert fw.run_until_settled() == 2
    doms = {ta_of(fw, "a").domain[:2], ta_of(fw, "b").domain[:2]}
    assert doms == {("block0", "rack0"), ("block0", "rack1")}
    assert int(fw.cache.topology.flavors["tpu"].sum()) == 6


# ---------------------------------------------------------------------------
# preemption: fragmentation-reducing victim preference
# ---------------------------------------------------------------------------


def _admit_with_topology(fw, name, leaf, rack, priority=0, creation=10.0):
    """Directly admit a 2-pod background workload occupying one host."""
    w = Workload(
        name=name, queue_name="main", priority=priority,
        creation_time=creation,
        pod_sets=[PodSet.make("main", 2, cpu=1)])
    w.admission = Admission(
        cluster_queue="cq",
        pod_set_assignments=[PodSetAssignment(
            name="main", flavors={"cpu": "tpu"},
            resource_usage={"cpu": 2000}, count=2,
            topology_assignment=TopologyAssignment(
                flavor="tpu", levels=("block", "rack"),
                domain=("block0", rack), counts=((leaf, 2),)))])
    w.set_condition("QuotaReserved", True, now=creation)
    w.set_condition("Admitted", True, now=creation)
    fw.workloads[w.key] = w
    fw.cache.add_or_update_workload(w)
    return w


def test_preemption_prefers_victims_freeing_one_domain(batch):
    # Quota full (8 cpu) and topology full (8 slots) with four 2-pod
    # low-priority workloads, two per rack, admission times INTERLEAVED
    # across racks — the reference ordering alone would evict the two
    # newest (one from each rack). The topology hint must steer eviction
    # to empty ONE rack instead.
    fw = build_fw(
        batch, cpu=8, counts=(1, 2, 2), leaf_capacity=2,
        preemption=ClusterQueuePreemption(
            within_cluster_queue="LowerPriority"))
    a = _admit_with_topology(fw, "a", leaf=0, rack="rack0", creation=10.0)
    b = _admit_with_topology(fw, "b", leaf=2, rack="rack1", creation=11.0)
    c = _admit_with_topology(fw, "c", leaf=1, rack="rack0", creation=12.0)
    d = _admit_with_topology(fw, "d", leaf=3, rack="rack1", creation=13.0)
    assert int(fw.cache.topology.flavors["tpu"].sum()) == 8

    fw.submit(wl("in", 4, required="rack", priority=5, cpu=1,
                 creation=100.0))
    fw.run_until_settled()
    evicted = {name for name in "abcd"
               if fw.workloads[f"default/{name}"].condition_true("Evicted")}
    # Without the preference the newest-first order would pick {c, d}
    # (one per rack); the hint groups rack0's occupants first.
    assert evicted == {"a", "c"}, evicted
    ta = ta_of(fw, "in")
    assert ta is not None and ta.domain[:2] == ("block0", "rack0")


# ---------------------------------------------------------------------------
# device/host fit equivalence on randomized instances
# ---------------------------------------------------------------------------


def test_fit_kernel_matches_host_referee_randomized():
    from kueue_tpu.topology import TopologyStage, build_topology_encoding
    from kueue_tpu.api.types import TopologyLeaf

    rng = np.random.RandomState(7)
    flavors = {
        "t1": topo_flavor("t1", counts=(2, 2, 2), leaf_capacity=4),
        "t2": topo_flavor("t2", counts=(1, 3, 2), leaf_capacity=3),
        # Irregular tree: hand-built leaves with mixed capacities.
        "t3": ResourceFlavor.make("t3", topology=TopologySpec(
            levels=("rack", "host"),
            leaves=(TopologyLeaf(("r0", "h0"), 5),
                    TopologyLeaf(("r0", "h1"), 1),
                    TopologyLeaf(("r1", "h0"), 2)))),
    }
    enc = build_topology_encoding(flavors)
    stage = TopologyStage(enc)
    T, E = len(enc.flavor_names), enc.E
    for trial in range(20):
        used = rng.randint(0, 5, size=(T, E)).astype(np.int64)
        items = []
        for _ in range(17):
            ti = int(rng.randint(T))
            nl = int(enc.num_levels[ti])
            items.append((ti, int(rng.randint(1, 10)),
                          int(rng.randint(nl)), bool(rng.randint(2))))
        host = stage._solve_items(items, used, use_device=False)
        dev = stage._solve_items(items, used, use_device=True)
        assert host == dev, f"trial {trial}: {host} != {dev}"


# ---------------------------------------------------------------------------
# serialization + ledger + gauges + no-op
# ---------------------------------------------------------------------------


def test_topology_serialization_roundtrips():
    rf = ResourceFlavor.make("tpu", topology=TopologySpec.uniform(
        ("rack", "host"), (2, 2), 3))
    doc = serialization.encode("ResourceFlavor", rf)
    _, back = serialization.decode(doc)
    assert back == rf

    w = wl("w", 3, required="rack")
    w.admission = Admission(
        cluster_queue="cq",
        pod_set_assignments=[PodSetAssignment(
            name="main", flavors={"cpu": "tpu"},
            resource_usage={"cpu": 3000}, count=3,
            topology_assignment=TopologyAssignment(
                flavor="tpu", levels=("rack",), domain=("rack0",),
                counts=((0, 2), (1, 1))))])
    doc = serialization.encode("Workload", w)
    _, back = serialization.decode(doc)
    serialization.decode_workload_status(doc, back)
    assert back.pod_sets[0].topology_required == "rack"
    assert back.admission.pod_set_assignments[0].topology_assignment \
        == w.admission.pod_set_assignments[0].topology_assignment
    # preferred roundtrips through the same stanza
    w2 = wl("w2", 3, preferred="host")
    _, back2 = serialization.decode(serialization.encode("Workload", w2))
    assert back2.pod_sets[0].topology_preferred == "host"
    assert back2.pod_sets[0].topology_required is None


def test_topology_webhook_rules():
    import kueue_tpu.webhooks as webhooks
    from kueue_tpu.api.types import TopologyLeaf

    bad = ResourceFlavor.make("f", topology=TopologySpec(
        levels=("rack", "rack"),
        leaves=(TopologyLeaf(("r0",), 0), TopologyLeaf(("r0",), 1))))
    errs = webhooks.validate_resource_flavor(bad)
    assert any("duplicate 'rack'" in e for e in errs)
    assert any("one value per level" in e for e in errs)
    assert any("capacity" in e for e in errs)
    assert any("duplicate leaf" in e for e in errs)

    both = wl("w", 1, required="rack")
    both.pod_sets[0].topology_preferred = "host"
    errs = webhooks.validate_workload(both)
    assert any("mutually exclusive" in e for e in errs)


def test_ledger_charges_and_releases_through_cache_rebuild():
    fw = build_fw(False, counts=(1, 2, 2), leaf_capacity=2)
    fw.submit(wl("a", 3, required="rack"))
    assert fw.run_until_settled() == 1
    assert int(fw.cache.topology.flavors["tpu"].sum()) == 3
    # A rebuilt cache (HA replay / restore path) re-accounts leaf state
    # from the recorded admissions.
    fw2 = build_fw(False, counts=(1, 2, 2), leaf_capacity=2)
    fw2.restore_workload(fw.workloads["default/a"])
    assert int(fw2.cache.topology.flavors["tpu"].sum()) == 3
    # Eviction / finish releases.
    fw.finish(fw.workloads["default/a"])
    assert int(fw.cache.topology.flavors["tpu"].sum()) == 0


def test_fragmentation_gauge_reports_per_level():
    from kueue_tpu.metrics import REGISTRY

    fw = build_fw(False, counts=(1, 2, 2), leaf_capacity=2)
    fw.submit(wl("a", 3, required="rack"))
    assert fw.run_until_settled() == 1
    fw.update_metrics_gauges()
    # rack level: rack0 has 1 free, rack1 has 4 -> frag = 1 - 4/5.
    assert REGISTRY.topology_fragmentation.get("tpu", "rack") \
        == pytest.approx(1.0 - 4.0 / 5.0)
    # block level: one block holds all free slots -> 0 fragmentation.
    assert REGISTRY.topology_fragmentation.get("tpu", "block") == 0.0


def test_topology_free_cluster_is_a_no_op(batch):
    """No flavor declares a topology: the snapshot view stays None, no
    stage is built, and topology-requesting workloads (preferred) admit
    unconstrained exactly like before the subsystem existed."""
    fw = Framework(batch_solver=BatchSolver() if batch else None)
    fw.create_resource_flavor(make_flavor("default"))
    fw.create_cluster_queue(make_cq("cq", rg("cpu", fq("default", cpu=8))))
    fw.create_local_queue(make_lq("main", cq="cq"))
    fw.submit(Workload(name="plain", queue_name="main",
                       pod_sets=[PodSet.make("m", 2, cpu=1)]))
    assert fw.run_until_settled() == 1
    assert fw.scheduler._mirror.refresh().topology is None
    assert fw.scheduler._topo_stage is None
    assert not fw.cache.topology.flavors
    psa = fw.workloads["default/plain"].admission.pod_set_assignments[0]
    assert psa.topology_assignment is None
