"""kueuetrace: span tracer, Chrome export, no-op goldens, explainability.

Pins the tentpole contracts of the tracing subsystem:

  * a DISABLED tracer records nothing (zero ring-buffer writes) and the
    scheduler's decisions are byte-identical with tracing on vs off —
    the no-op proof, run over a preemption + borrowing scenario under
    both the referee and the batched device solver;
  * the Chrome trace-event export validates against the event-format
    schema (loads in Perfetto) and nests phases inside the tick span;
  * head+tail sampling: the slowest tick survives ring eviction;
  * per-workload admission explainability records every flavor tried
    with its verdict, surfaced through the visibility server and the
    Dumper.
"""

import json

import pytest

from kueue_tpu.api.serialization import encode
from kueue_tpu.api.types import ClusterQueuePreemption
from kueue_tpu.controllers.debugger import Dumper
from kueue_tpu.controllers.runtime import Framework
from kueue_tpu.controllers.visibility import VisibilityServer
from kueue_tpu.models.flavor_fit import BatchSolver
from kueue_tpu.tracing import TRACER, ExplainStore, Tracer
from kueue_tpu.tracing.tracer import NULL_SPAN, validate_chrome_trace

from tests.test_pods_ready import FakeClock
from tests.util import fq, make_cq, make_flavor, make_lq, make_wl, rg


@pytest.fixture(autouse=True)
def _tracer_off():
    """Every test starts from the default (disabled, empty) tracer."""
    TRACER.configure(enabled=False)
    TRACER.reset()
    yield
    TRACER.configure(enabled=False)
    TRACER.reset()


# ---------------------------------------------------------------------------
# Tracer core
# ---------------------------------------------------------------------------


def test_disabled_tracer_is_noop():
    t = Tracer(enabled=False)
    assert t.span("x") is NULL_SPAN
    assert t.tick() is NULL_SPAN
    lock = __import__("threading").Lock()
    assert t.lock(lock, "l") is lock  # the plain `with lock:` path
    with t.span("x") as sp:
        sp.set("k", "v")  # no-op
    with t.phase("snapshot"):
        pass  # histogram-only timer
    assert t.ticks() == []
    assert t.export_chrome()["otherData"]["ticks_retained"] == 0


def test_phase_feeds_histogram_enabled_and_disabled():
    from kueue_tpu.metrics import REGISTRY

    totals = REGISTRY.tick_phase_seconds.totals
    for enabled in (False, True):
        t = Tracer(enabled=enabled)
        before = totals.get(("trace-test-phase",), 0)
        with t.phase("trace-test-phase"):
            pass
        assert totals[("trace-test-phase",)] == before + 1


def test_span_nesting_and_attributes_in_export():
    t = Tracer(enabled=True)
    with t.tick() as tick_span:
        with t.span("outer") as sp:
            sp.set("bucket", [8, 1, 2])
            with t.span("inner"):
                pass
        tick_span.set("admitted", 3)
    doc = t.export_chrome()
    assert validate_chrome_trace(doc) == []
    by_name = {ev["name"]: ev for ev in doc["traceEvents"]
               if ev["ph"] == "X"}
    assert {"tick", "outer", "inner"} <= set(by_name)
    outer, inner = by_name["outer"], by_name["inner"]
    # Time containment (what Perfetto nests by): inner within outer
    # within tick.
    tick = by_name["tick"]
    assert tick["ts"] <= outer["ts"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"]["bucket"] == [8, 1, 2]
    assert tick["args"]["admitted"] == 3


def test_ring_eviction_keeps_slowest_tick():
    import time

    t = Tracer(enabled=True, ring_size=4, keep_slowest=2)
    for i in range(12):
        with t.tick():
            if i == 3:  # the slow outlier, long evicted from a 4-ring
                time.sleep(0.02)
    ticks = t.ticks()
    # 4 recent + the retained slowest (dedup by seq).
    assert len(ticks) <= 6
    assert t.slowest_tick().seq == 4  # seq is 1-based
    assert any(rec.seq == 4 for rec in ticks)
    assert ticks[-1].seq == 12


def test_lock_span_times_acquisition_and_holds():
    import threading

    t = Tracer(enabled=True)
    lock = threading.Lock()
    with t.lock(lock, "queue.lock_wait"):
        assert lock.locked()
    assert not lock.locked()
    spans = list(t._loose)
    assert [s.name for s in spans] == ["queue.lock_wait"]


def test_chrome_schema_validator_rejects_malformed():
    assert validate_chrome_trace([]) == ["top level must be a JSON object"]
    assert validate_chrome_trace({"traceEvents": "no"}) \
        == ["traceEvents must be a list"]
    bad = {"traceEvents": [{"name": "", "ph": "X", "ts": -1, "pid": "x"}]}
    problems = validate_chrome_trace(bad)
    assert len(problems) >= 3  # name, ts, pid (+ tid/dur)


def test_export_json_roundtrips():
    t = Tracer(enabled=True)
    with t.tick():
        with t.span("admit.flush"):
            pass
    doc = json.loads(t.export_json())
    assert validate_chrome_trace(doc) == []


# ---------------------------------------------------------------------------
# No-op goldens: tracing off == tracing on, decision for decision
# ---------------------------------------------------------------------------


def _scenario(batch: bool) -> Framework:
    """Preemption + borrowing + two flavors: every decision shape the
    explain/trace machinery touches (FIT, borrow, PREEMPT victims,
    NoFit requeue) in one fixture."""
    fw = Framework(batch_solver=BatchSolver() if batch else None,
                   clock=FakeClock())
    for f in ("on-demand", "spot"):
        fw.create_resource_flavor(make_flavor(f))
    fw.create_cluster_queue(make_cq(
        "cq-a", rg("cpu", fq("on-demand", cpu=4)), cohort="co",
        preemption=ClusterQueuePreemption(
            within_cluster_queue="LowerPriority")))
    # Pure lender: its spot quota is the pool cq-b borrows from.
    fw.create_cluster_queue(make_cq(
        "cq-lend", rg("cpu", fq("spot", cpu=4)), cohort="co"))
    fw.create_cluster_queue(make_cq(
        "cq-b", rg("cpu", fq("spot", cpu=(1, 8))), cohort="co"))
    fw.create_local_queue(make_lq("lq-a", cq="cq-a"))
    fw.create_local_queue(make_lq("lq-b", cq="cq-b"))
    fw.submit(make_wl("low", "lq-a", cpu=4, priority=-1, creation_time=1.0))
    fw.run_until_settled()
    # high preempts low on cq-a; borrower leans on the cohort's spot
    # pool via cq-b; parked exceeds even the borrowing limit.
    fw.submit(make_wl("high", "lq-a", cpu=4, priority=5, creation_time=2.0))
    fw.submit(make_wl("borrower", "lq-b", cpu=3, creation_time=3.0))
    fw.submit(make_wl("parked", "lq-b", cpu=32, creation_time=4.0))
    fw.run_until_settled()
    return fw


def _decision_state(fw: Framework) -> str:
    docs = []
    for _, wl in sorted(fw.workloads.items()):
        doc = encode("Workload", wl)
        # The uid counter is process-global (monotonic across Framework
        # instances); it identifies the object, it is not a decision.
        doc.get("metadata", {}).pop("uid", None)
        docs.append(doc)
    return json.dumps(docs, sort_keys=True)


@pytest.mark.parametrize("batch", [False, True], ids=["referee", "batched"])
def test_tracing_disabled_vs_enabled_decisions_identical(batch):
    TRACER.configure(enabled=False)
    state_off = _decision_state(_scenario(batch))
    TRACER.configure(enabled=True)
    state_on = _decision_state(_scenario(batch))
    assert state_on == state_off  # byte-identical decisions
    # And the traced run actually recorded ticks.
    assert TRACER.ticks()


def test_disabled_run_writes_nothing_to_ring():
    TRACER.configure(enabled=False)
    _scenario(batch=False)
    assert TRACER.ticks() == []
    assert len(TRACER._loose) == 0


def test_traced_tick_contains_pipeline_phases(monkeypatch):
    # Force the CSR commit so the csr_rows span attribute is exercised
    # regardless of whether the native ledger walk is built.
    monkeypatch.setenv("KUEUE_TPU_CSR_ASSUME", "1")
    TRACER.configure(enabled=True)
    _scenario(batch=True)
    names = {s.name for rec in TRACER.ticks() for s in rec.spans}
    assert {"tick", "snapshot", "nominate", "admit", "admit.flush",
            "requeue", "reconcile", "tensorize", "device_solve",
            "decode"} <= names
    doc = TRACER.export_chrome()
    assert validate_chrome_trace(doc) == []
    # The solver dispatch span carries the compile-proof attributes.
    tens = [ev for ev in doc["traceEvents"]
            if ev["name"] == "tensorize" and ev["ph"] == "X"]
    assert tens and all(
        ev["args"]["engine"] == "batch-packed-xla"
        and isinstance(ev["args"]["bucket"], list)
        and isinstance(ev["args"]["cold_dispatches"], int)
        for ev in tens)
    # The encode span carries the incremental-arena evidence: how many
    # rows this tick's gather re-encoded vs its total, and whether the
    # arena was rebuilt wholesale (encoding rotation).
    enc = [ev for ev in doc["traceEvents"]
           if ev["name"] == "tensorize.encode" and ev["ph"] == "X"]
    assert enc and all(
        isinstance(ev["args"]["rows_dirty"], int)
        and isinstance(ev["args"]["rows_total"], int)
        and isinstance(ev["args"]["full_rebuild"], bool)
        and ev["args"]["rows_dirty"] <= ev["args"]["rows_total"]
        for ev in enc)
    # At least one gather ran against an already-seeded arena: pure reuse.
    assert any(ev["args"]["rows_dirty"] == 0 and ev["args"]["rows_total"]
               for ev in enc)
    # The snapshot delta-flush span reports its ClusterQueue fan-out.
    flushes = [ev for ev in doc["traceEvents"]
               if ev["name"] == "snapshot.flush" and ev["ph"] == "X"]
    assert flushes and all(
        isinstance(ev["args"]["cqs_flushed"], int)
        and isinstance(ev["args"]["items"], int)
        and 0 < ev["args"]["cqs_flushed"] <= ev["args"]["items"]
        for ev in flushes)
    # The nominate span carries the fingerprint-cache split: replayed
    # heads vs the tick's total.
    noms = [ev for ev in doc["traceEvents"]
            if ev["name"] == "nominate" and ev["ph"] == "X"
            and "heads_total" in ev.get("args", {})]
    assert noms and all(
        isinstance(ev["args"]["heads_cached"], int)
        and isinstance(ev["args"]["heads_total"], int)
        and 0 <= ev["args"]["heads_cached"] <= ev["args"]["heads_total"]
        for ev in noms)
    # The bulk-assume span names its commit shape: how many entries the
    # cycle reserved and how many CSR coordinate rows the aggregated
    # commit consumed (0 = the classic per-entry walk ran).
    assumes = [ev for ev in doc["traceEvents"]
               if ev["name"] == "admit.flush.assume" and ev["ph"] == "X"]
    assert assumes and all(
        isinstance(ev["args"]["entries"], int)
        and isinstance(ev["args"]["csr_rows"], int)
        and ev["args"]["entries"] > 0
        for ev in assumes)
    assert any(ev["args"]["csr_rows"] > 0 for ev in assumes), \
        "no flush took the CSR commit path in the batched scenario"


# ---------------------------------------------------------------------------
# Admission explainability
# ---------------------------------------------------------------------------


def test_explain_records_flavors_and_verdicts():
    fw = _scenario(batch=False)
    explain = fw.scheduler.explain
    # The admitted borrower's last decision names the flavor it
    # borrowed on.
    last = explain.last_decision("default/borrower")
    assert last["outcome"] == "Admitted"
    assert last["clusterQueue"] == "cq-b"
    assert {(f["flavor"], f["verdict"]) for f in last["flavors"]} \
        == {("spot", "Fit")}
    assert any(f["borrow"] for f in last["flavors"])
    # The preemptor's story: a Preempting attempt before admission.
    history = explain.for_workload("default/high")
    assert history[-1]["outcome"] == "Admitted"
    assert any(r["outcome"] == "Preempting"
               and r.get("preemptionTargets", 0) == 1 for r in history)
    # The never-fitting workload records why.
    parked = explain.last_decision("default/parked")
    assert parked["outcome"] == "Inadmissible"
    assert "borrowing limit for cpu in flavor spot exceeded" \
        in parked["reason"]


def test_explain_store_bounds_and_lru():
    store = ExplainStore(per_workload=2, max_workloads=3)
    for i in range(5):
        for attempt in range(4):
            store.record(f"wl-{i}", (attempt, 0.0, "cq", "Skipped", "",
                                     (), None, 0))
    assert store.occupancy == 3  # LRU capped
    assert store.for_workload("wl-0") == []  # evicted
    recs = store.for_workload("wl-4")
    assert [r["tick"] for r in recs] == [2, 3]  # per-workload deque cap
    store.forget("wl-4")
    assert store.occupancy == 2


def test_visibility_explain_param_attaches_decisions():
    fw = _scenario(batch=False)
    vis = VisibilityServer(fw.queues, explain=fw.scheduler.explain)
    plain = vis.pending_workloads_in_cq("cq-b")
    assert [p.name for p in plain] == ["parked"]
    assert plain[0].decisions is None
    explained = vis.pending_workloads_in_cq("cq-b", explain=True)
    decisions = explained[0].decisions
    assert decisions, "?explain=true must attach the decision history"
    assert decisions[-1]["outcome"] == "Inadmissible"
    flavors = {f["flavor"] for f in decisions[-1]["flavors"]} | {
        f["flavor"] for d in decisions for f in d["flavors"]}
    # Every flavor the CQ could try appears with a verdict somewhere in
    # the recorded story (parked fits nowhere, so none may be a Fit).
    assert all(f["verdict"] != "Fit"
               for d in decisions for f in d["flavors"])


def test_visibility_lq_explain_attaches_decisions():
    fw = _scenario(batch=False)
    vis = VisibilityServer(fw.queues, explain=fw.scheduler.explain)
    mine = vis.pending_workloads_in_lq("default", "lq-b", explain=True)
    assert [p.name for p in mine] == ["parked"]
    assert mine[0].decisions
    assert mine[0].decisions[-1]["outcome"] == "Inadmissible"
    # Without explain the page carries no records.
    assert vis.pending_workloads_in_lq(
        "default", "lq-b")[0].decisions is None


def test_dumper_includes_events_and_explain():
    fw = _scenario(batch=False)
    dump = json.loads(Dumper(fw.cache, fw.queues, events=fw.events,
                             explain=fw.scheduler.explain).dump_json())
    assert dump["events"]["capacity"] == 10_000
    assert dump["events"]["occupancy"] >= 1
    assert dump["events"]["dropped"] == 0
    assert dump["explain"]["workloads"] >= 3
    assert "default/parked" in dump["explain"]["lastDecisions"]
