"""Multi-host transport: frame codec, fault injection, the reliable
socket channel, and the two-"host" identity golden vs the pipe transport
(kueue_tpu/transport/).

The codec contract: partial frames across arbitrarily split reads decode
identically to one big read, torn trailing frames stay pending (and die
with the connection — the reconnect handshake retransmits them whole),
and the fault schedule is a pure function of (seed, channel id) so fault
drills are reproducible. The channel contract: exactly-once in-order
delivery across severed connections, injected drops, and reordered
frames. The deployment contract: a socket-transport replica deployment
with SEPARATE per-host state directories replays the pipe-transport
(and single-process) decision trail byte for byte.
"""

import tempfile

import pytest

from kueue_tpu import features
from kueue_tpu.transport import (
    ChannelListener,
    FaultPlan,
    FrameDecoder,
    FrameError,
    SocketChannel,
    WorkerDiedError,
    decode_message,
    encode_frame,
    encode_message,
    parse_fault_env,
)
from kueue_tpu.transport.faults import PASS

from tests.test_replica import _ReplicaTarget, _SingleTarget, drive


# -- frame codec -------------------------------------------------------------


def test_codec_roundtrip_and_partial_reads():
    msgs = [("tick", 3, True), {"op": "round", "usage": {"f": {"cpu": 2}}},
            ("verdicts", [True, False]), ("objs", [[0, {"kind": "W"}]])]
    blob = b"".join(encode_message(m) for m in msgs)
    # Whole-blob feed and byte-by-byte feed decode identically.
    whole = [decode_message(p) for p in FrameDecoder().feed(blob)]
    dec = FrameDecoder()
    dribble = []
    for i in range(len(blob)):
        dribble.extend(decode_message(p) for p in dec.feed(blob[i:i + 1]))
    assert whole == dribble
    assert dec.pending_bytes == 0
    # Tuples survive the JSON wire at the top level (the transports'
    # message shape); nested containers are positional, lists are fine.
    assert dribble[0] == ("tick", 3, True)
    assert dribble[1]["usage"]["f"]["cpu"] == 2


def test_codec_torn_trailing_frame_stays_pending():
    dec = FrameDecoder()
    blob = encode_message(("a",)) + encode_message(("b", 2))
    torn = blob[:-3]  # killed mid-append
    frames = dec.feed(torn)
    assert [decode_message(p) for p in frames] == [("a",)]
    assert dec.pending_bytes > 0  # the torn write, visibly incomplete
    # The retransmitted whole frame completes it.
    frames = dec.feed(blob[-3:])
    assert [decode_message(p) for p in frames] == [("b", 2)]


def test_codec_rejects_desynced_stream():
    dec = FrameDecoder()
    with pytest.raises(FrameError):
        dec.feed(b"\xff\xff\xff\xff garbage that is not a frame header")


def test_encode_frame_layout():
    payload = b'{"x":1}'
    frame = encode_frame(payload)
    assert frame[:4] == len(payload).to_bytes(4, "big")
    assert frame[4:] == payload


# -- fault injection ---------------------------------------------------------


def test_fault_schedule_is_seed_deterministic():
    plan = FaultPlan(seed=11, drop_prob=0.1, reorder_prob=0.2,
                     delay_prob=0.4, delay_ms=1)
    a = [plan.injector("chan-x").next_action() for _ in range(200)]
    b = [plan.injector("chan-x").next_action() for _ in range(200)]
    assert a == b  # same seed + channel -> same schedule
    c = [plan.injector("chan-y").next_action() for _ in range(200)]
    assert a != c  # channels draw independent schedules
    assert any(x != PASS for x in a)  # the mix actually fires
    d = [FaultPlan(seed=12, drop_prob=0.1, reorder_prob=0.2,
                   delay_prob=0.4, delay_ms=1)
         .injector("chan-x").next_action() for _ in range(200)]
    assert a != d  # the seed matters


def test_parse_fault_env():
    plan = parse_fault_env("delay_ms=5,delay_p=0.5,drop_p=0.01,seed=7")
    assert plan == FaultPlan(seed=7, delay_ms=5, delay_prob=0.5,
                             drop_prob=0.01)
    assert parse_fault_env("") is None
    assert parse_fault_env("delay_ms=5") is None  # no probability: inert
    with pytest.raises(ValueError):
        parse_fault_env("bogus_knob=1")


# -- the reliable channel ----------------------------------------------------


def _pair(plan=None):
    lis = ChannelListener(plan=plan)
    ep = lis.endpoint(0)
    ch = SocketChannel.connect(lis.address, 0, plan=plan)
    return lis, ep, ch


def test_channel_delivers_both_directions():
    lis, ep, ch = _pair()
    try:
        ep.send(("down", 1))
        ch.send(("up", 2))
        assert ch.recv(timeout=10) == ("down", 1)
        assert ep.recv(timeout=10) == ("up", 2)
    finally:
        ch.close(); ep.close(); lis.close()


def test_channel_recv_timeout_raises():
    lis, ep, ch = _pair()
    try:
        with pytest.raises(WorkerDiedError):
            ch.recv(timeout=0.05)
    finally:
        ch.close(); ep.close(); lis.close()


def test_channel_reconnect_and_resume_exactly_once():
    """Sever the connection repeatedly mid-stream: every message still
    arrives exactly once, in order — the seq/ack/retransmit layer."""
    lis, ep, ch = _pair()
    try:
        got = []
        for i in range(30):
            ep.send(("n", i))
            if i % 7 == 3:
                ch.sever()       # connector-side loss
            if i % 11 == 5:
                ep.sever()       # listener-side loss
            if i % 3 == 0:
                got.append(ch.recv(timeout=10))
        while len(got) < 30:
            got.append(ch.recv(timeout=10))
        assert got == [("n", i) for i in range(30)]
    finally:
        ch.close(); ep.close(); lis.close()


def test_channel_survives_fault_storm_in_order():
    """Seeded drop/reorder/delay storm: delivery stays exactly-once and
    ordered in both directions (drop severs + resumes, reorder is
    absorbed by resequencing)."""
    import time

    plan = FaultPlan(seed=3, drop_prob=0.05, reorder_prob=0.15,
                     delay_prob=0.3, delay_ms=1)
    lis, ep, ch = _pair(plan=plan)
    try:
        deadline = time.time() + 10
        while not (ch.connected and ep.connected):
            assert time.time() < deadline, "never connected"
            time.sleep(0.01)
        n = 120
        for i in range(n):
            ep.send(("m", i))
            ch.send(("r", i))
        assert [ch.recv(timeout=15) for _ in range(n)] \
            == [("m", i) for i in range(n)]
        assert [ep.recv(timeout=15) for _ in range(n)] \
            == [("r", i) for i in range(n)]
        fired = ep._faults.stats.to_dict()
        assert sum(fired.values()) > 0, f"storm never fired: {fired}"
    finally:
        ch.close(); ep.close(); lis.close()


def test_channel_reorder_fault_really_reorders_the_wire():
    """A pure-reorder storm must put frames on the wire OUT of order —
    provable by the receiver's resequencing hold counter — while
    delivery stays in order. (Regression: an earlier fault path flushed
    the held frame before every write, silently preserving wire order
    and drilling nothing.)"""
    plan = FaultPlan(seed=2, reorder_prob=0.5)
    lis, ep, ch = _pair(plan=plan)
    try:
        import time

        deadline = time.time() + 10
        while not (ch.connected and ep.connected):
            assert time.time() < deadline, "never connected"
            time.sleep(0.01)
        n = 60
        for i in range(n):
            ep.send(("m", i))
        assert [ch.recv(timeout=15) for _ in range(n)] \
            == [("m", i) for i in range(n)]
        assert ep._faults.stats.reorders > 0
        assert ch.resequenced > 0, \
            "reorder faults fired but the wire order never changed"
    finally:
        ch.close(); ep.close(); lis.close()


def test_channel_buffers_before_first_connect():
    """Sends before the peer ever dialed deliver after the handshake
    (the runtime routes objects to workers as soon as they spawn)."""
    lis = ChannelListener()
    ep = lis.endpoint(4)
    try:
        for i in range(5):
            ep.send(("early", i))
        ch = SocketChannel.connect(lis.address, 4)
        try:
            assert [ch.recv(timeout=10) for _ in range(5)] \
                == [("early", i) for i in range(5)]
        finally:
            ch.close()
    finally:
        ep.close(); lis.close()


# -- the two-"host" identity golden ------------------------------------------


def _expected_trail():
    target = _SingleTarget(None)
    try:
        return drive(target, ticks=40)
    finally:
        target.close()


class _SocketTarget(_ReplicaTarget):
    """The replica harness on the SOCKET transport with separate
    per-host state dirs — two emulated hosts over loopback TCP."""

    def __init__(self, replicas, state_dir, faults=None):
        from kueue_tpu.controllers.replica_runtime import ReplicaRuntime
        from tests.test_replica import _apply_world

        features.set_enabled(features.LENDING_LIMIT, True)
        self.rt = ReplicaRuntime(replicas, spawn=False, engine="host",
                                 state_dir=state_dir, transport="socket",
                                 faults=faults)
        _apply_world(self.rt)
        self._revocations = 0


def test_two_host_socket_identity_vs_pipe_transport():
    """Two emulated hosts (separate state dirs, loopback sockets, the
    framed reconcile protocol end to end, split KEP-79 tree included)
    replay the single-process decision trail byte for byte — the
    socket transport is decision-invisible, exactly like the pipe
    transport it replaces."""
    expect = _expected_trail()
    with tempfile.TemporaryDirectory() as td:
        target = _SocketTarget(2, state_dir=td)
        try:
            trail = drive(target, ticks=40)
            assert target.rt.transport == "socket"
            assert target.rt.per_host
        finally:
            target.close()
    assert trail == expect


def test_two_host_socket_identity_with_injected_delay():
    """The same golden WITH seeded packet-delay injection: latency
    faults shift reconcile RTT, never decisions."""
    expect = _expected_trail()
    with tempfile.TemporaryDirectory() as td:
        target = _SocketTarget(
            2, state_dir=td,
            faults=FaultPlan(seed=5, delay_ms=2, delay_prob=0.3))
        try:
            trail = drive(target, ticks=40)
        finally:
            target.close()
    assert trail == expect


def test_no_socket_kill_switch_forces_pipe(monkeypatch):
    from kueue_tpu.controllers.replica_runtime import (
        ReplicaRuntime,
        transport_from_env,
    )

    monkeypatch.setenv("KUEUE_TPU_NO_SOCKET", "1")
    assert transport_from_env("socket") == "pipe"
    rt = ReplicaRuntime(2, spawn=False, engine="host", transport="socket")
    try:
        assert rt.transport == "pipe"
        assert rt.listener is None
    finally:
        rt.close()
