"""Digital-twin tests: trace model, generators, engine determinism,
what-if harness, and the twin-vs-drive() byte-identity cross-check.
The 10^6-scale replay runs via `make twin-smoke` at a smaller budget;
here the contracts are pinned at test scale."""

import copy
import json

import pytest

from kueue_tpu.fuzz import generator as fuzz_gen, lattice
from kueue_tpu.fuzz.lattice import LatticePoint
from kueue_tpu.twin import (CapacityConfig, DurationModel, Trace,
                            TwinEngine, apply_config, default_sweep,
                            parse_config, replay, twin_cluster)
from kueue_tpu.twin import crosscheck, generators, whatif


def small_gen(shape="diurnal_heavy", workloads=400, days=0.25,
              seed=11, cqs=8, **kw):
    gen = {"shape": shape, "workloads": workloads, "days": days,
           "seed": seed, "cqs": cqs, "mean_duration_s": 900.0}
    gen.update(kw)
    return gen


def small_trace(**kw):
    gen = small_gen(**kw)
    quota = generators.size_cluster_quota(gen, gen["cqs"])
    cluster = twin_cluster(num_cqs=gen["cqs"], num_cohorts=4,
                           cpu_quota=quota["cpu"],
                           memory_gi_quota=quota["memory_gi"])
    return Trace(name="t", seed=gen["seed"], cluster=cluster,
                 generator=gen, tick_interval_s=600.0)


# -- trace model ------------------------------------------------------------


def test_trace_json_roundtrip():
    tr = small_trace()
    again = Trace.from_dict(json.loads(tr.to_json()))
    assert again.to_dict() == tr.to_dict()
    with pytest.raises(ValueError):
        Trace.from_dict({"format": "not-a-trace"})


def test_trace_loads_fuzz_scenario_and_reproducer_formats():
    """The format bridge: a kueuefuzz/v1 scenario dict and a
    kueuefuzz-repro/v1 reproducer both load as PACED traces."""
    sc = fuzz_gen.draw_scenario(5)
    tr = Trace.from_dict(sc.to_dict())
    assert tr.paced
    assert tr.cluster["cluster_queues"] == sc.cluster_queues
    assert sum(1 for e in tr.events if e[0] == "tick") \
        == sc.ticks + sc.settle_ticks
    repro = {"format": "kueuefuzz-repro/v1", "name": "r",
             "scenario": sc.to_dict()}
    tr2 = Trace.from_dict(repro)
    assert tr2.paced and tr2.events == tr.events


def test_twin_cluster_is_scenario_language():
    cluster = twin_cluster(num_cqs=4, num_cohorts=2, cpu_quota=8)
    tr = Trace(name="c", seed=0, cluster=cluster, events=[])
    sc = tr.cluster_scenario()
    assert len(sc.cluster_queues) == 4
    # The LocalQueue naming contract the generators rely on:
    # lq_object(cq) names the queue "lq-<cq-name>".
    from kueue_tpu.fuzz.scenario import lq_object, nominal_capacity
    assert lq_object(sc.cluster_queues[0]).name == "lq-cq-0"
    caps = nominal_capacity(sc, {})
    assert caps  # the quota oracle can price the twin cluster


# -- generators -------------------------------------------------------------


def test_generator_streams_are_deterministic_and_sized():
    gen = small_gen(workloads=300)
    a = list(generators.iter_generator(gen, 0.0))
    b = list(generators.iter_generator(gen, 0.0))
    assert a == b
    n = sum(1 for _v, k, _p in a if k == "submit")
    assert n == 300
    times = [v for v, _k, _p in a]
    assert times == sorted(times)
    assert all(0.0 <= v <= gen["days"] * 86400.0 for v in times)
    c = list(generators.iter_generator(dict(gen, seed=12), 0.0))
    assert c != a


@pytest.mark.parametrize("shape", generators.SHAPES)
def test_every_shape_streams_valid_specs(shape):
    gen = small_gen(shape=shape, workloads=120)
    subs = spikes = 0
    for _v, kind, payload in generators.iter_generator(gen, 0.0):
        if kind == "submit":
            subs += 1
            assert payload["queue"].startswith("lq-cq-")
            assert payload["pod_sets"][0]["cpu"] >= 1
            assert payload["duration_s"] >= 60.0
        else:
            assert kind == "spike"
            spikes += payload["n"]
    assert subs + spikes == 120
    if shape == "adversarial_burst":
        assert spikes > 0


def test_size_cluster_quota_carries_offered_load():
    gen = small_gen(workloads=2000, days=0.5)
    q = generators.size_cluster_quota(gen, 8)
    assert q["cpu"] >= 2 and q["memory_gi"] >= 2
    # Double the load, the sizing grows.
    q2 = generators.size_cluster_quota(
        dict(gen, workloads=4000), 8)
    assert q2["cpu"] > q["cpu"]


# -- engine -----------------------------------------------------------------


def test_twin_determinism_same_trace_identical_timeline():
    """The twin determinism oracle: same trace + seed => identical
    timeline, metrics (minus wall-clock), and final admitted set."""
    tr = small_trace()

    def strip(res):
        m = {k: v for k, v in res["metrics"].items()
             if not k.startswith("wall") and k != "workloads_per_wall_s"}
        return (res["timeline"], m, res["final_admitted"],
                res["high_water"], res["violation_count"])

    a = replay(tr, engine="referee")
    b = replay(tr, engine="referee")
    assert strip(a) == strip(b)


def test_twin_replays_to_completion_with_physical_waits():
    tr = small_trace(workloads=300)
    res = replay(tr, engine="referee")
    m = res["metrics"]
    assert m["workloads_submitted"] == 300
    # Heavy-tailed draws include giants beyond the cohort root's total
    # capacity: those legally strand (NoFit forever) and the twin
    # reports them instead of hanging. Everything feasible completes.
    assert m["completed"] + m["stranded_pending"] == 300
    assert m["completed"] >= 270
    assert m["quota_violations"] == 0
    # Submit->admit waits are bounded by the discretization: an
    # uncongested trace admits within ~a tick interval.
    assert m["wait_p50_s"] is not None
    assert 0.0 <= m["wait_p50_s"] <= 2 * tr.tick_interval_s
    # Timeline rows are [vtime, admitted, preempted, completed,
    # pending, live] and conserve the workload count.
    assert sum(r[1] for r in res["timeline"]) >= m["completed"]
    assert sum(r[3] for r in res["timeline"]) == m["completed"]


def test_twin_engines_agree_on_the_same_trace():
    """referee / host / jax replays of one trace reach the same
    timeline — the fuzz identity promise, restated at the twin's
    level."""
    tr = small_trace(workloads=250)
    rows = [replay(tr, engine=e)["timeline"]
            for e in ("referee", "host", "jax")]
    assert rows[0] == rows[1] == rows[2]


def test_adversarial_burst_spikes_preempt_or_queue():
    """Spike expansion: one spike event becomes n submits; with
    preemption enabled the high-priority burst evicts baseline load."""
    gen = small_gen(shape="adversarial_burst", workloads=300,
                    spikes=2)
    quota = generators.size_cluster_quota(gen, gen["cqs"])
    cluster = twin_cluster(
        num_cqs=gen["cqs"], num_cohorts=4,
        cpu_quota=max(2, quota["cpu"] // 2),
        memory_gi_quota=max(2, quota["memory_gi"] // 2),
        preemption={"within": "LowerPriority", "reclaim": "Any"})
    tr = Trace(name="burst", seed=gen["seed"], cluster=cluster,
               generator=gen)
    res = replay(tr, engine="referee")
    assert res["metrics"]["spikes"] == 2
    assert res["metrics"]["workloads_submitted"] == 300
    assert res["metrics"]["quota_violations"] == 0


def test_fast_workload_equals_scenario_workload_object():
    # The trusted bulk-ingest constructor must build the SAME object
    # the full scenario path builds — dataclass equality over every
    # field — and must refuse anything it can't replicate exactly.
    from kueue_tpu.fuzz import scenario as sc_mod
    from kueue_tpu.twin.engine import TwinEngine

    specs = [
        {"name": "w-0", "queue": "lq-cq-0", "priority": 0,
         "creation_time": 1_000_000.0,
         "pod_sets": [{"name": "ps0", "count": 1, "cpu": 2,
                       "memory_gi": 4, "topo": None}],
         "tputs": None},
        {"name": "w-1", "queue": "lq-cq-3", "priority": 4,
         "creation_time": 1_000_600.5,
         "pod_sets": [{"name": "ps0", "count": 8, "cpu": 13,
                       "memory_gi": 1, "topo": None},
                      {"name": "ps1", "count": 2, "cpu": 1,
                       "memory_gi": 64, "topo": None}],
         "tputs": None},
    ]
    import dataclasses

    for spec in specs:
        fast = TwinEngine._fast_workload(spec)
        assert fast is not None
        full = sc_mod.workload_object(spec)
        # uid is a process-global creation counter — the only field
        # that can differ, and only because this test builds the same
        # spec twice (a real replay builds each workload once).
        assert dataclasses.replace(fast, uid=full.uid) == full

    topo = dict(specs[0])
    topo["pod_sets"] = [{"name": "ps0", "count": 1, "cpu": 1,
                         "memory_gi": 1,
                         "topo": ("required", "rack")}]
    assert TwinEngine._fast_workload(topo) is None
    tput = dict(specs[0])
    tput["tputs"] = {"flavor-0": 2.0}
    assert TwinEngine._fast_workload(tput) is None


def test_duration_model_learns_and_falls_back():
    dm = DurationModel(default_s=111.0)
    assert dm.estimate("cq-0") == 111.0
    dm.observe("cq-0", 100.0)
    assert dm.estimate("cq-0") == 100.0
    assert dm.estimate("cq-1") == 100.0   # global EWMA fallback
    dm.observe("cq-0", 200.0)
    assert 100.0 < dm.estimate("cq-0") < 200.0


# -- what-if ----------------------------------------------------------------


def test_parse_config_round_trips_the_spec_language():
    cfg = parse_config(
        "ladder:quota=1.5,flavor.flavor-0=0.5,speed.flavor-1=2.0,"
        "shards=2,engine=host")
    assert cfg.name == "ladder"
    assert cfg.quota_factor == 1.5
    assert cfg.flavor_factors == {"flavor-0": 0.5}
    assert cfg.speed_factors == {"flavor-1": 2.0}
    assert cfg.shards == 2 and cfg.engine == "host"
    assert parse_config("baseline").quota_factor == 1.0
    with pytest.raises(ValueError):
        parse_config("x:bogus=1")
    with pytest.raises(ValueError):
        parse_config("x:quota")


def test_apply_config_scales_quota_triples_pure():
    cluster = twin_cluster(num_cqs=2, num_flavors=2, cpu_quota=10)
    before = copy.deepcopy(cluster)
    out = apply_config(cluster, CapacityConfig(
        name="x", quota_factor=2.0, flavor_factors={"flavor-1": 0.5},
        speed_factors={"flavor-0": 3.0}))
    assert cluster == before           # pure: input untouched
    q = out["cluster_queues"][0]["quotas"]
    assert q["flavor-0"]["cpu"][0] == 20
    assert q["flavor-1"]["cpu"][0] == 10   # 10 * 2.0 * 0.5
    assert out["flavors"][0]["speed_class"] == 3.0
    # None (unlimited) stays None under any resize.
    q["flavor-0"]["cpu"][1] is None


def test_whatif_sweep_compares_configs():
    tr = small_trace(workloads=250)
    report = whatif.sweep(
        tr, [CapacityConfig(name="baseline"),
             CapacityConfig(name="squeeze", quota_factor=0.3)],
        default_engine="referee")
    assert report["format"] == whatif.REPORT_FORMAT
    assert report["baseline"] == "baseline"
    names = [r["name"] for r in report["configs"]]
    assert names == ["baseline", "squeeze"]
    squeeze = report["configs"][1]
    assert "delta_vs_baseline" in squeeze
    # A 70% quota cut must not improve p99 wait.
    base_p99 = report["configs"][0]["metrics"]["wait_p99_s"]
    sq_p99 = squeeze["metrics"]["wait_p99_s"]
    assert sq_p99 >= base_p99
    # Quota oracle holds under every config (the sweep's "ok").
    assert all(r["quota_violations"] == 0 for r in report["configs"])
    assert report["ok"]
    assert "squeeze" in whatif.format_report(report)


def test_default_sweep_is_three_configs():
    names = [c.name for c in default_sweep()]
    assert names == ["baseline", "quota-75", "quota-150"]


# -- cross-check ------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_twin_byte_matches_drive_on_lattice_scenarios(seed):
    """THE truthfulness gate: paced replay of a fuzz scenario is
    byte-identical to lattice.drive() — trail, final admitted set, and
    oracle violations — at every engine."""
    sc = fuzz_gen.draw_scenario(seed)
    res = crosscheck.crosscheck_scenario(sc)
    assert res["ok"], json.dumps(res, indent=1, default=list)
    assert {p["engine"] for p in res["points"]} \
        == {"host", "jax", "referee"}
    assert all(p["byte_identical"] for p in res["points"])


def test_crosscheck_detects_a_lying_twin(monkeypatch):
    """If the twin's decisions drift from drive()'s, the byte gate
    must go red — prove the comparator can actually fail."""
    sc = fuzz_gen.draw_scenario(0)
    real_run = TwinEngine.run

    def lying_run(self):
        res = real_run(self)
        if res.get("trail"):
            res["trail"] = list(res["trail"])
            res["trail"][-1] = (("default/phantom",), ())
        return res

    monkeypatch.setattr(TwinEngine, "run", lying_run)
    res = crosscheck.crosscheck_scenario(sc, engines=("host",))
    assert not res["ok"]
    assert res["points"][0]["divergence"] is not None


def test_paced_replay_of_converted_scenario_runs_ops():
    """A converted scenario's traffic ops (finish/update_cq/...) apply
    through the shared FrameworkTrafficDriver selectors."""
    sc = fuzz_gen.draw_scenario(4)
    tr = Trace.from_scenario(sc)
    res = TwinEngine(tr, engine="host", record_trail=True).run()
    ref = lattice.drive(sc, LatticePoint(name="x", kind="framework",
                                         engine="host"))
    assert res["trail"] == ref["trail"]
    assert res["final_admitted"] == ref["final_admitted"]
