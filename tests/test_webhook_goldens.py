"""Webhook validation goldens — transliterated reference decision tables.

Sources:
- /root/reference/pkg/webhooks/workload_webhook_test.go
  (TestValidateWorkload :97-393, TestValidateWorkloadUpdate :395-693)
- /root/reference/pkg/webhooks/clusterqueue_webhook_test.go
  (TestValidateClusterQueue :34-429, TestValidateClusterQueueUpdate :431-462)

The reference asserts field paths (Detail/BadValue ignored); these goldens
pin the same verdicts by asserting each expected path appears in exactly
the produced error strings (our errors are "path: detail" strings). Rows
whose trigger cannot exist in this API surface are recorded N/A inline:
- "should have priority once priorityClassName is set": Workload.priority
  is a non-optional int here; the reference checks a nil pointer.
- container-level checks are expressed at the PodSet.requests level (the
  canonical request form of this API).
"""

import pytest

from kueue_tpu import features
from kueue_tpu.api.types import (
    Admission,
    AdmissionCheckState,
    BorrowWithinCohort,
    ClusterQueue,
    ClusterQueuePreemption,
    FlavorQuotas,
    LabelSelector,
    MatchExpression,
    PodSet,
    PodSetAssignment,
    ResourceGroup,
    Workload,
)
from kueue_tpu.webhooks.validation import (
    validate_cluster_queue,
    validate_cluster_queue_update,
    validate_workload,
    validate_workload_update,
)


def assert_paths(errs, want_paths):
    """Every expected path must prefix exactly one error; counts match
    (the reference's cmp.Diff on field paths)."""
    unmatched = list(errs)
    missing = []
    for path in want_paths:
        hit = next((e for e in unmatched if e.startswith(path)), None)
        if hit is None:
            missing.append(path)
        else:
            unmatched.remove(hit)
    assert not missing and not unmatched, (
        f"want paths {want_paths}\n got errors {errs}\n"
        f" missing={missing} unexpected={unmatched}")


def wl(name="wl", pod_sets=None, **kw):
    if pod_sets is None:
        pod_sets = [PodSet.make("main", 1)]
    return Workload(name=name, namespace="default", pod_sets=pod_sets, **kw)


def reserve(workload, cq="cluster-queue", psa_names=None, assignments=None):
    names = psa_names if psa_names is not None \
        else [ps.name for ps in workload.pod_sets]
    psas = assignments if assignments is not None else [
        PodSetAssignment(name=n, flavors={}, resource_usage={}, count=1)
        for n in names]
    workload.admission = Admission(cluster_queue=cq, pod_set_assignments=psas)
    workload.set_condition("QuotaReserved", True)
    return workload


# -- TestValidateWorkload (workload_webhook_test.go:97) ---------------------

WORKLOAD_CASES = [
    ("valid", lambda: wl(pod_sets=[
        PodSet.make("driver", 1), PodSet.make("workers", 100)]), []),
    ("invalid podSet name", lambda: wl(pod_sets=[
        PodSet.make("@driver", 1)]), ["spec.podSets[0].name"]),
    ("invalid priorityClassName", lambda: wl(
        priority_class="invalid_class", priority=0),
        ["spec.priorityClassName"]),
    ("empty priorityClassName ok", lambda: wl(), []),
    # N/A: "should have priority once priorityClassName is set" —
    # priority is non-optional in this API (reference checks nil).
    ("invalid queueName", lambda: wl(queue_name="@invalid"),
        ["spec.queueName"]),
    ("invalid clusterQueue name in admission", lambda: reserve(
        wl(), cq="@invalid"), ["status.admission.clusterQueue"]),
    ("invalid podSet name in status assignment", lambda: reserve(
        wl(), psa_names=["@invalid"]),
        ["status.admission.podSetAssignments"]),
    # Reference emits Invalid + NotFound for the extra assignment; this
    # build reports the set mismatch as one error on the same path.
    ("same podSets in admission", lambda: reserve(
        wl(pod_sets=[PodSet.make("main2", 1), PodSet.make("main1", 1)]),
        psa_names=["main1", "main2", "main3"]),
        ["status.admission.podSetAssignments"]),
    ("assignment usage divisible by count", lambda: reserve(
        wl(pod_sets=[PodSet.make("main", 3, cpu=1)]),
        assignments=[PodSetAssignment(
            name="main", flavors={"cpu": "flv"},
            resource_usage={"cpu": 1000}, count=3)]),
        ["status.admission.podSetAssignments[0].resourceUsage[cpu]"]),
    ("should not request num-pods resource", lambda: wl(pod_sets=[
        PodSet(name="bad", count=1, requests={"pods": 1})]),
        ["spec.podSets[0].requests"]),
    ("empty podSetUpdates", lambda: wl(admission_check_states={
        "ac": AdmissionCheckState(name="ac", state="Pending")}), []),
    ("podSetUpdates count mismatch", lambda: wl(
        pod_sets=[PodSet.make("first", 1), PodSet.make("second", 1)],
        admission_check_states={"ac": AdmissionCheckState(
            name="ac", state="Pending",
            pod_set_updates=[{"name": "first"}])}),
        ["status.admissionChecks[ac].podSetUpdates"]),
    ("podSetUpdates mismatched names", lambda: wl(
        pod_sets=[PodSet.make("first", 1), PodSet.make("second", 1)],
        admission_check_states={"ac": AdmissionCheckState(
            name="ac", state="Pending",
            pod_set_updates=[{"name": "first"}, {"name": "third"}])}),
        ["status.admissionChecks[ac].podSetUpdates[1].name"]),
    ("podSetUpdates matched names valid maps", lambda: wl(
        pod_sets=[PodSet.make("first", 1), PodSet.make("second", 1)],
        admission_check_states={"ac": AdmissionCheckState(
            name="ac", state="Pending",
            pod_set_updates=[
                {"name": "first", "labels": {"l1": "first"},
                 "annotations": {"foo": "bar"},
                 "nodeSelector": {"type": "first"}},
                {"name": "second", "labels": {"l2": "second"},
                 "annotations": {"foo": "baz"},
                 "nodeSelector": {"type": "second"}}])}), []),
    ("podSetUpdates invalid label key", lambda: wl(
        admission_check_states={"ac": AdmissionCheckState(
            name="ac", state="Pending",
            pod_set_updates=[{"name": "main",
                              "labels": {"@abc": "foo"}}])}),
        ["status.admissionChecks[ac].podSetUpdates[0].labels"]),
    ("podSetUpdates invalid nodeSelector key", lambda: wl(
        admission_check_states={"ac": AdmissionCheckState(
            name="ac", state="Pending",
            pod_set_updates=[{"name": "main",
                              "nodeSelector": {"@abc": "foo"}}])}),
        ["status.admissionChecks[ac].podSetUpdates[0].nodeSelector"]),
    ("podSetUpdates invalid label value", lambda: wl(
        admission_check_states={"ac": AdmissionCheckState(
            name="ac", state="Pending",
            pod_set_updates=[{"name": "main",
                              "labels": {"foo": "@abc"}}])}),
        ["status.admissionChecks[ac].podSetUpdates[0].labels"]),
    ("invalid reclaimablePods", lambda: wl(
        pod_sets=[PodSet.make("ps1", 3)],
        reclaimable_pods={"ps1": 4, "ps2": 1}),
        ["status.reclaimablePods[ps1].count",
         "status.reclaimablePods[ps2]"]),
    ("minCount negative", lambda: wl(pod_sets=[
        PodSet(name="ps1", count=3, min_count=-1)]),
        ["spec.podSets[0].minCount"]),
    ("minCount too big", lambda: wl(pod_sets=[
        PodSet(name="ps1", count=3, min_count=4)]),
        ["spec.podSets[0].minCount"]),
    ("too many variable count podSets", lambda: wl(pod_sets=[
        PodSet(name="ps1", count=3, min_count=2),
        PodSet(name="ps2", count=3, min_count=1)]),
        ["spec.podSets"]),
]


@pytest.mark.parametrize("name,builder,want",
                         WORKLOAD_CASES, ids=[c[0] for c in WORKLOAD_CASES])
def test_validate_workload_golden(name, builder, want):
    assert_paths(validate_workload(builder()), want)


# -- TestValidateWorkloadUpdate (workload_webhook_test.go:395) --------------

def _two_ps():
    return [PodSet.make("ps1", 3), PodSet.make("ps2", 3)]


UPDATE_CASES = [
    ("podSets immutable when reserved: count",
     lambda: reserve(wl()),
     lambda: wl(pod_sets=[PodSet.make("main", 2)]),
     ["spec.podSets"]),
    # Reference mutates the pod template spec; the schedulable analog in
    # this API is the per-pod requests map.
    ("podSets immutable when reserved: requests",
     lambda: reserve(wl()),
     lambda: wl(pod_sets=[PodSet.make("main", 1, cpu=1)]),
     ["spec.podSets"]),
    ("queueName can change when not admitted",
     lambda: wl(queue_name="q1"), lambda: wl(queue_name="q2"), []),
    ("queueName can change when admitting",
     lambda: wl(), lambda: reserve(wl(queue_name="q")), []),
    ("queueName immutable once admitted",
     lambda: reserve(wl(queue_name="q1")),
     lambda: reserve(wl(queue_name="q2")),
     ["spec.queueName"]),
    ("queueName can change when admission reset",
     lambda: reserve(wl(queue_name="q1")), lambda: wl(queue_name="q2"), []),
    ("admission can be set",
     lambda: wl(), lambda: reserve(wl()), []),
    ("admission can be unset",
     lambda: reserve(wl()), lambda: wl(), []),
    ("admission immutable once set",
     lambda: reserve(wl()),
     lambda: reserve(wl(), assignments=[PodSetAssignment(
         name="main", flavors={"cpu": "on-demand"},
         resource_usage={"cpu": 5000}, count=1)]),
     ["status.admission"]),
    ("reclaimable pod count can change up",
     lambda: reserve(wl(pod_sets=_two_ps(), reclaimable_pods={"ps1": 1})),
     lambda: reserve(wl(pod_sets=_two_ps(),
                        reclaimable_pods={"ps1": 2, "ps2": 1})),
     []),
    ("reclaimable pod count cannot change down",
     lambda: reserve(wl(pod_sets=_two_ps(),
                        reclaimable_pods={"ps1": 2, "ps2": 1})),
     lambda: reserve(wl(pod_sets=_two_ps(), reclaimable_pods={"ps1": 1})),
     ["status.reclaimablePods[ps1].count",
      "status.reclaimablePods[ps2]"]),
    ("reclaimable can go to 0 when suspended",
     lambda: reserve(wl(pod_sets=_two_ps(),
                        reclaimable_pods={"ps1": 2, "ps2": 1})),
     lambda: wl(pod_sets=_two_ps(),
                reclaimable_pods={"ps1": 0, "ps2": 1},
                admission_check_states={"ac": AdmissionCheckState(
                    name="ac", state="Ready",
                    pod_set_updates=[{"name": "ps1"}, {"name": "ps2"}])}),
     []),
    ("priorityClassSource immutable after reservation",
     lambda: reserve(wl(queue_name="q", priority_class="test-class",
                        priority_class_source="pod", priority=10)),
     lambda: wl(queue_name="q", priority_class="test-class",
                priority_class_source="workload", priority=10),
     ["spec.priorityClassSource"]),
    ("priorityClassName immutable after reservation",
     lambda: reserve(wl(queue_name="q", priority_class="test-class-1",
                        priority_class_source="pod", priority=10)),
     lambda: wl(queue_name="q", priority_class="test-class-2",
                priority_class_source="pod", priority=10),
     ["spec.priorityClassName"]),
    ("podSetUpdates immutable when check Ready",
     lambda: wl(pod_sets=[PodSet.make("first", 1),
                          PodSet.make("second", 1)],
                admission_check_states={"ac": AdmissionCheckState(
                    name="ac", state="Ready",
                    pod_set_updates=[
                        {"name": "first", "labels": {"foo": "bar"}},
                        {"name": "second"}])}),
     lambda: wl(pod_sets=[PodSet.make("first", 1),
                          PodSet.make("second", 1)],
                admission_check_states={"ac": AdmissionCheckState(
                    name="ac", state="Ready",
                    pod_set_updates=[
                        {"name": "first", "labels": {"foo": "baz"}},
                        {"name": "second"}])}),
     ["status.admissionChecks[ac].podSetUpdates"]),
    ("other admissioncheck fields can change when Ready",
     lambda: wl(pod_sets=[PodSet.make("first", 1),
                          PodSet.make("second", 1)],
                admission_check_states={"ac1": AdmissionCheckState(
                    name="ac1", state="Ready", message="old",
                    pod_set_updates=[
                        {"name": "first", "labels": {"foo": "bar"}},
                        {"name": "second"}])}),
     lambda: wl(pod_sets=[PodSet.make("first", 1),
                          PodSet.make("second", 1)],
                admission_check_states={"ac1": AdmissionCheckState(
                    name="ac1", state="Ready", message="new",
                    pod_set_updates=[
                        {"name": "first", "labels": {"foo": "bar"}},
                        {"name": "second"}])}),
     []),
    ("priorityClassName can change before reservation",
     lambda: wl(queue_name="q", priority_class="test-class-1",
                priority_class_source="pod", priority=10),
     lambda: wl(queue_name="q", priority_class="test-class-2",
                priority_class_source="pod", priority=10),
     []),
    ("priorityClassSource can change before reservation",
     lambda: wl(queue_name="q", priority_class="test-class",
                priority_class_source="pod", priority=10),
     lambda: wl(queue_name="q", priority_class="test-class",
                priority_class_source="workload", priority=10),
     []),
    ("podSets can change before reservation",
     lambda: wl(),
     lambda: wl(pod_sets=[PodSet.make("main", 1, cpu=2)]),
     []),
]


@pytest.mark.parametrize("name,before,after,want",
                         UPDATE_CASES, ids=[c[0] for c in UPDATE_CASES])
def test_validate_workload_update_golden(name, before, after, want):
    assert_paths(validate_workload_update(after(), before()), want)


# -- TestValidateClusterQueue (clusterqueue_webhook_test.go:34) -------------

def cq(name="cluster-queue", groups=None, cohort=None, **kw):
    if groups is None:
        groups = ()
    return ClusterQueue(name=name, resource_groups=tuple(groups),
                        cohort=cohort, **kw)


def group(resources, *flavor_quotas):
    return ResourceGroup(tuple(resources), tuple(flavor_quotas))


CQ_CASES = [
    ("built-in resources", lambda: cq(groups=[
        group(["cpu"], FlavorQuotas.make("default", cpu=0))]), [], False),
    ("invalid resource name", lambda: cq(groups=[
        group(["@cpu"], FlavorQuotas.make("default", **{"@cpu": 0}))]),
        # Our quotas-must-match rule compares names too and both carry
        # the invalid name, so only the coveredResources error fires.
        ["spec.resourceGroups[0].coveredResources"], False),
    ("in cohort", lambda: cq(cohort="prod"), [], False),
    ("invalid cohort", lambda: cq(cohort="@prod"), ["spec.cohort"], False),
    ("extended resource names", lambda: cq(groups=[
        group(["example.com/gpu"],
              FlavorQuotas(name="default",
                           resources=_quota("example.com/gpu", 0)))]),
        [], False),
    ("flavor qualified name", lambda: cq(groups=[
        group([], FlavorQuotas(name="x86", resources=()))]), [], False),
    ("flavor unqualified name", lambda: cq(groups=[
        group([], FlavorQuotas(name="invalid_name", resources=()))]),
        ["spec.resourceGroups[0].flavors[0].name"], False),
    ("negative nominal quota", lambda: cq(groups=[
        group(["cpu"], FlavorQuotas(name="x86",
                                    resources=_quota("cpu", -1)))]),
        ["spec.resourceGroups[0].flavors[0].resources[cpu].nominalQuota"],
        False),
    ("zero nominal quota", lambda: cq(groups=[
        group(["cpu"], FlavorQuotas.make("x86", cpu=0))]), [], False),
    ("borrowingLimit 0 in cohort", lambda: cq(cohort="cohort", groups=[
        group(["cpu"], FlavorQuotas.make("x86", cpu=(1, 0)))]), [], False),
    ("negative borrowingLimit", lambda: cq(cohort="cohort", groups=[
        group(["cpu"], FlavorQuotas(name="x86",
                                    resources=_quota("cpu", 1, -1)))]),
        ["spec.resourceGroups[0].flavors[0].resources[cpu].borrowingLimit"],
        False),
    ("borrowingLimit with empty cohort", lambda: cq(groups=[
        group(["cpu"], FlavorQuotas.make("x86", cpu=(1, 1)))]),
        ["spec.resourceGroups[0].flavors[0].resources[cpu].borrowingLimit"],
        False),
    ("lendingLimit 0 in cohort", lambda: cq(cohort="cohort", groups=[
        group(["cpu"], FlavorQuotas.make("x86", cpu=(1, None, 0)))]),
        [], True),
    ("negative lendingLimit", lambda: cq(cohort="cohort", groups=[
        group(["cpu"], FlavorQuotas(name="x86",
                                    resources=_quota("cpu", 1, None, -1)))]),
        ["spec.resourceGroups[0].flavors[0].resources[cpu].lendingLimit"],
        True),
    ("lendingLimit with empty cohort", lambda: cq(groups=[
        group(["cpu"], FlavorQuotas.make("x86", cpu=(1, None, 1)))]),
        ["spec.resourceGroups[0].flavors[0].resources[cpu].lendingLimit"],
        True),
    ("lendingLimit above nominal", lambda: cq(cohort="cohort", groups=[
        group(["cpu"], FlavorQuotas.make("x86", cpu=(1, None, 2)))]),
        ["spec.resourceGroups[0].flavors[0].resources[cpu].lendingLimit"],
        True),
    # N/A: "empty queueing strategy is supported" — the dataclass default
    # fills BestEffortFIFO; an empty string is not representable distinct
    # from the default.
    ("namespaceSelector invalid label key", lambda: cq(
        namespace_selector=LabelSelector(
            match_labels=(("nospecialchars^=@", "bar"),))),
        ["spec.namespaceSelector.matchLabels"], False),
    ("namespaceSelector In without values", lambda: cq(
        namespace_selector=LabelSelector(match_expressions=(
            MatchExpression("key", "In", ()),))),
        ["spec.namespaceSelector.matchExpressions[0].values"], False),
    ("multiple resource groups", lambda: cq(groups=[
        group(["cpu", "memory"],
              FlavorQuotas.make("alpha", cpu=0, memory=0),
              FlavorQuotas.make("beta", cpu=0, memory=0)),
        group(["example.com/gpu"],
              FlavorQuotas(name="gamma",
                           resources=_quota("example.com/gpu", 0)),
              FlavorQuotas(name="omega",
                           resources=_quota("example.com/gpu", 0)))]),
        [], False),
    # Reference emits one error per out-of-order resource; this build
    # reports the flavor-level mismatch once.
    ("resources in a flavor out of order", lambda: cq(groups=[
        group(["cpu", "memory"],
              FlavorQuotas.make("alpha", cpu=0, memory=0),
              FlavorQuotas.make("beta", memory=0, cpu=0))]),
        ["spec.resourceGroups[0].flavors[1].resources"], False),
    ("missing resources in a flavor", lambda: cq(groups=[
        group(["cpu", "memory"], FlavorQuotas.make("alpha", cpu=0))]),
        ["spec.resourceGroups[0].flavors[0].resources"], False),
    ("extra resources in a flavor", lambda: cq(groups=[
        group(["cpu"], FlavorQuotas.make("alpha", cpu=0, memory=0))]),
        ["spec.resourceGroups[0].flavors[0].resources"], False),
    ("missing resources and name mismatch", lambda: cq(groups=[
        group(["blah"], FlavorQuotas.make("alpha", cpu=0, memory=0))]),
        ["spec.resourceGroups[0].flavors[0].resources"], False),
    ("resource in two groups", lambda: cq(groups=[
        group(["cpu", "memory"],
              FlavorQuotas.make("alpha", cpu=0, memory=0)),
        group(["memory"], FlavorQuotas.make("beta", memory=0))]),
        ["spec.resourceGroups[1].coveredResources"], False),
    ("flavor in two groups", lambda: cq(groups=[
        group(["cpu"], FlavorQuotas.make("alpha", cpu=0),
              FlavorQuotas.make("beta", cpu=0)),
        group(["memory"], FlavorQuotas.make("beta", memory=0))]),
        ["spec.resourceGroups[1].flavors[0].name"], False),
    ("reclaim Never with borrowWithinCohort", lambda: cq(
        preemption=ClusterQueuePreemption(
            reclaim_within_cohort="Never",
            borrow_within_cohort=BorrowWithinCohort(
                policy="LowerPriority"))),
        ["spec.preemption"], False),
    ("valid borrowWithinCohort", lambda: cq(
        preemption=ClusterQueuePreemption(
            reclaim_within_cohort="LowerPriority",
            borrow_within_cohort=BorrowWithinCohort(
                policy="LowerPriority", max_priority_threshold=10))),
        [], False),
    ("nil borrowWithinCohort with reclaim Never", lambda: cq(
        preemption=ClusterQueuePreemption(reclaim_within_cohort="Never")),
        [], False),
]


def _quota(rname, nominal, borrow=None, lend=None):
    from kueue_tpu.api.types import ResourceQuota
    return ((rname, ResourceQuota(nominal=nominal, borrowing_limit=borrow,
                                  lending_limit=lend)),)


@pytest.mark.parametrize("name,builder,want,lending",
                         CQ_CASES, ids=[c[0] for c in CQ_CASES])
def test_validate_cluster_queue_golden(name, builder, want, lending):
    features.set_enabled(features.LENDING_LIMIT, lending)
    assert_paths(validate_cluster_queue(builder()), want)


# -- TestValidateClusterQueueUpdate (clusterqueue_webhook_test.go:431) ------

def test_queueing_strategy_immutable():
    new = cq(queueing_strategy="BestEffortFIFO")
    old = cq(queueing_strategy="StrictFIFO")
    assert_paths(validate_cluster_queue_update(new, old),
                 ["spec.queueingStrategy"])


def test_queueing_strategy_same():
    new = cq(queueing_strategy="BestEffortFIFO")
    old = cq(queueing_strategy="BestEffortFIFO")
    assert_paths(validate_cluster_queue_update(new, old), [])
