"""Webhook defaulting + validation tests.

Mirrors the reference's table-driven webhook tests
(pkg/webhooks/*_webhook_test.go) at the rule level.
"""

import pytest

from kueue_tpu import webhooks
from kueue_tpu.api.types import (
    AdmissionCheck,
    BorrowWithinCohort,
    ClusterQueue,
    ClusterQueuePreemption,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    Taint,
    Workload,
)
from kueue_tpu.controllers.runtime import Framework


def make_cq(name="cq", cohort="", **kw):
    return ClusterQueue(
        name=name, cohort=cohort,
        resource_groups=(ResourceGroup(
            covered_resources=("cpu",),
            flavors=(FlavorQuotas.make("default", cpu=10),)),),
        **kw)


class TestClusterQueueValidation:
    def test_valid(self):
        assert webhooks.validate_cluster_queue(make_cq()) == []

    def test_borrowing_limit_requires_cohort(self):
        cq = ClusterQueue(
            name="cq",
            resource_groups=(ResourceGroup(
                covered_resources=("cpu",),
                flavors=(FlavorQuotas.make("default", cpu=(10, 5)),)),))
        errs = webhooks.validate_cluster_queue(cq)
        assert any("borrowingLimit" in e and "cohort" in e for e in errs)

    def test_lending_limit_exceeds_nominal(self):
        cq = ClusterQueue(
            name="cq", cohort="team",
            resource_groups=(ResourceGroup(
                covered_resources=("cpu",),
                flavors=(FlavorQuotas.make("default", cpu=(10, None, 20)),)),))
        errs = webhooks.validate_cluster_queue(cq)
        assert any("lendingLimit" in e and "nominalQuota" in e for e in errs)

    def test_flavor_resources_must_match_covered(self):
        cq = ClusterQueue(
            name="cq",
            resource_groups=(ResourceGroup(
                covered_resources=("cpu", "memory"),
                flavors=(FlavorQuotas.make("default", cpu=10),)),))
        errs = webhooks.validate_cluster_queue(cq)
        assert any("coveredResources" in e for e in errs)

    def test_duplicate_flavor(self):
        cq = ClusterQueue(
            name="cq",
            resource_groups=(
                ResourceGroup(covered_resources=("cpu",),
                              flavors=(FlavorQuotas.make("f1", cpu=10),)),
                ResourceGroup(covered_resources=("memory",),
                              flavors=(FlavorQuotas.make("f1", memory=10),)),
            ))
        errs = webhooks.validate_cluster_queue(cq)
        assert any("duplicate flavor" in e for e in errs)

    def test_reclaim_never_with_borrow_within_cohort(self):
        cq = make_cq(cohort="team", preemption=ClusterQueuePreemption(
            reclaim_within_cohort="Never",
            borrow_within_cohort=BorrowWithinCohort(policy="LowerPriority")))
        errs = webhooks.validate_cluster_queue(cq)
        assert any("borrowWithinCohort" in e for e in errs)

    def test_queueing_strategy_immutable(self):
        old = make_cq()
        new = make_cq(queueing_strategy="StrictFIFO")
        errs = webhooks.validate_cluster_queue_update(new, old)
        assert any("queueingStrategy" in e and "immutable" in e for e in errs)

    def test_framework_rejects_invalid(self):
        fw = Framework()
        with pytest.raises(webhooks.ValidationError):
            fw.create_cluster_queue(ClusterQueue(
                name="cq",
                resource_groups=(ResourceGroup(
                    covered_resources=("cpu",),
                    flavors=(FlavorQuotas.make("default", cpu=(10, 5)),)),)))


class TestWorkloadValidation:
    def test_valid(self):
        wl = Workload(name="w", pod_sets=[PodSet.make("main", 2, cpu=1)])
        assert webhooks.validate_workload(wl) == []

    def test_default_podset_name(self):
        wl = Workload(name="w", pod_sets=[PodSet.make("", 1, cpu=1)])
        webhooks.default_workload(wl)
        assert wl.pod_sets[0].name == "main"

    def test_at_most_one_variable_count_podset(self):
        wl = Workload(name="w", pod_sets=[
            PodSet.make("a", 4, min_count=1, cpu=1),
            PodSet.make("b", 4, min_count=2, cpu=1)])
        errs = webhooks.validate_workload(wl)
        assert any("minCount" in e for e in errs)

    def test_invalid_podset_name(self):
        wl = Workload(name="w", pod_sets=[PodSet.make("Main_Set", 1, cpu=1)])
        errs = webhooks.validate_workload(wl)
        assert any("DNS-1123" in e for e in errs)

    def test_count_minimum(self):
        wl = Workload(name="w", pod_sets=[PodSet.make("main", 0, cpu=1)])
        errs = webhooks.validate_workload(wl)
        assert any("count" in e for e in errs)

    def test_reclaimable_bounds(self):
        wl = Workload(name="w", pod_sets=[PodSet.make("main", 2, cpu=1)])
        wl.reclaimable_pods = {"main": 3}
        errs = webhooks.validate_workload(wl)
        assert any("reclaimablePods" in e for e in errs)

    def test_podsets_immutable_after_reservation(self):
        old = Workload(name="w", pod_sets=[PodSet.make("main", 2, cpu=1)])
        old.set_condition("QuotaReserved", True)
        new = Workload(name="w", pod_sets=[PodSet.make("main", 3, cpu=1)])
        new.set_condition("QuotaReserved", True)
        errs = webhooks.validate_workload_update(new, old)
        assert any("podSets" in e and "immutable" in e for e in errs)

    def test_reclaimable_cannot_shrink_while_reserved(self):
        old = Workload(name="w", pod_sets=[PodSet.make("main", 4, cpu=1)])
        old.set_condition("QuotaReserved", True)
        old.reclaimable_pods = {"main": 2}
        new = Workload(name="w", pod_sets=[PodSet.make("main", 4, cpu=1)])
        new.set_condition("QuotaReserved", True)
        new.reclaimable_pods = {"main": 1}
        errs = webhooks.validate_workload_update(new, old)
        assert any("cannot be less" in e for e in errs)


class TestOtherKinds:
    def test_local_queue_cq_immutable(self):
        old = LocalQueue(name="lq", namespace="default", cluster_queue="a")
        new = LocalQueue(name="lq", namespace="default", cluster_queue="b")
        errs = webhooks.validate_local_queue_update(new, old)
        assert any("immutable" in e for e in errs)

    def test_resource_flavor_taint_effect(self):
        rf = ResourceFlavor.make(
            "f", node_taints=[Taint(key="gpu", effect="Sometimes")])
        errs = webhooks.validate_resource_flavor(rf)
        assert any("effect" in e for e in errs)

    def test_resource_flavor_valid(self):
        rf = ResourceFlavor.make(
            "f", node_labels={"cloud/zone": "us-1"},
            node_taints=[Taint(key="gpu", value="true", effect="NoSchedule")])
        assert webhooks.validate_resource_flavor(rf) == []

    def test_admission_check_controller_required(self):
        ac = AdmissionCheck(name="ac", controller_name="")
        errs = webhooks.validate_admission_check(ac)
        assert any("controllerName" in e for e in errs)

    def test_admission_check_controller_immutable(self):
        old = AdmissionCheck(name="ac", controller_name="a")
        new = AdmissionCheck(name="ac", controller_name="b")
        errs = webhooks.validate_admission_check_update(new, old)
        assert any("immutable" in e for e in errs)


class TestCohortValidation:
    """Cohort structural rules (KEP-79; same rule set as ClusterQueues)."""

    def _cohort(self, name="co", parent="", groups=()):
        from kueue_tpu.api.types import CohortSpec
        return CohortSpec(name=name, parent=parent,
                          resource_groups=tuple(groups))

    def test_duplicate_flavor_rejected(self):
        from kueue_tpu.webhooks.validation import validate_cohort
        from tests.util import fq, rg
        spec = self._cohort(parent="root", groups=[
            rg("cpu", fq("f1", cpu=1), fq("f1", cpu=2))])
        assert any("duplicate flavor" in e for e in validate_cohort(spec))

    def test_duplicate_resource_rejected(self):
        from kueue_tpu.webhooks.validation import validate_cohort
        from tests.util import fq, rg
        spec = self._cohort(parent="root", groups=[
            rg("cpu", fq("f1", cpu=1)), rg("cpu", fq("f2", cpu=2))])
        assert any("duplicate 'cpu'" in e for e in validate_cohort(spec))

    def test_group_cap(self):
        from kueue_tpu.webhooks.validation import validate_cohort
        from tests.util import fq, rg
        groups = [rg(f"res{i}", fq(f"f{i}", **{f"res{i}": 1}))
                  for i in range(17)]
        spec = self._cohort(parent="root", groups=groups)
        assert any("at most 16" in e for e in validate_cohort(spec))

    def test_root_cohort_borrowing_limit_rejected(self):
        from kueue_tpu.api.types import FlavorQuotas, ResourceQuota
        from kueue_tpu.webhooks.validation import validate_cohort
        from tests.util import rg
        f = FlavorQuotas(name="f1", resources=(
            ("cpu", ResourceQuota(nominal=1000, borrowing_limit=500)),))
        spec = self._cohort(groups=[rg("cpu", f)])  # no parent = root
        assert any("borrowingLimit" in e and "root Cohort" in e
                   for e in validate_cohort(spec))
        # With a parent the same spec is fine.
        spec = self._cohort(parent="root", groups=[rg("cpu", f)])
        assert validate_cohort(spec) == []

    def test_cohort_lending_limit_may_exceed_nominal(self):
        from kueue_tpu.api.types import FlavorQuotas, ResourceQuota
        from kueue_tpu.webhooks.validation import validate_cohort
        from tests.util import rg
        f = FlavorQuotas(name="f1", resources=(
            ("cpu", ResourceQuota(nominal=0, lending_limit=2000)),))
        spec = self._cohort(parent="root", groups=[rg("cpu", f)])
        assert validate_cohort(spec) == []


class TestClusterQueueGroupCap:
    def test_group_cap(self):
        from kueue_tpu.webhooks.validation import validate_cluster_queue
        from tests.util import fq, make_cq, rg
        groups = [rg(f"res{i}", fq(f"f{i}", **{f"res{i}": 1}))
                  for i in range(17)]
        cq = make_cq("cq", *groups)
        assert any("at most 16" in e for e in validate_cluster_queue(cq))
