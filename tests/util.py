"""Fluent test builders (counterpart of reference pkg/util/testing/wrappers.go)."""

from __future__ import annotations

from typing import Optional

from kueue_tpu.api.types import (
    BorrowWithinCohort,
    ClusterQueue,
    ClusterQueuePreemption,
    FlavorFungibility,
    FlavorQuotas,
    LabelSelector,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    Workload,
)


def make_flavor(name: str, **labels) -> ResourceFlavor:
    return ResourceFlavor.make(name, node_labels=labels or None)


def make_cq(name: str, *groups: ResourceGroup, cohort: str = "",
            strategy: str = "BestEffortFIFO",
            preemption: Optional[ClusterQueuePreemption] = None,
            fungibility: Optional[FlavorFungibility] = None,
            namespace_selector: Optional[LabelSelector] = None,
            admission_checks=()) -> ClusterQueue:
    kwargs = {}
    if preemption is not None:
        kwargs["preemption"] = preemption
    if fungibility is not None:
        kwargs["flavor_fungibility"] = fungibility
    if namespace_selector is not None:
        kwargs["namespace_selector"] = namespace_selector
    return ClusterQueue(
        name=name, resource_groups=tuple(groups), cohort=cohort,
        queueing_strategy=strategy, admission_checks=tuple(admission_checks),
        **kwargs)


def rg(resources, *flavors: FlavorQuotas) -> ResourceGroup:
    if isinstance(resources, str):
        resources = (resources,)
    return ResourceGroup(covered_resources=tuple(resources),
                         flavors=tuple(flavors))


def fq(name: str, **quotas) -> FlavorQuotas:
    return FlavorQuotas.make(name, **quotas)


def make_lq(name: str = "main", namespace: str = "default",
            cq: str = "cq") -> LocalQueue:
    return LocalQueue(name=name, namespace=namespace, cluster_queue=cq)


_wl_seq = [0]


def make_wl(name: str, cq_or_lq: str = "main", priority: int = 0,
            creation_time: Optional[float] = None, namespace: str = "default",
            pod_sets=None, **requests) -> Workload:
    _wl_seq[0] += 1
    if pod_sets is None:
        pod_sets = [PodSet.make("main", count=1, **requests)]
    return Workload(
        name=name, namespace=namespace, queue_name=cq_or_lq,
        pod_sets=list(pod_sets), priority=priority,
        creation_time=creation_time if creation_time is not None else float(_wl_seq[0]),
    )
